#!/bin/bash
# Post-campaign: recapture the regression gate against round-5 results,
# regenerate the BASELINE tables, and sanity-run the gate check.
cd /root/repo
set -x
python tools/regression_gate.py capture || exit 1
python tools/regression_gate.py check || exit 1
python tools/insert_baseline_tables.py || exit 1
echo POST_CAMPAIGN_R5_DONE
