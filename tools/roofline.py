"""Single-chip roofline/utilization ledger for the full-pool epoch.

VERDICT r4 next #1: the headline (YCSB theta=0.9 full-pool TPU_BATCH) had
been ~6.05M txn/s for three rounds with no accounting of where the
epoch's milliseconds go or how close they run to what the chip can do.
This tool produces that ledger from the ONLY measurement that proved
reliable on this tunneled chip: an `xprof` trace of the real jitted scan,
summed per HLO op (phase microbenchmarks each carry ~100 ms of per-call
RPC overhead and mislead; see git history of this file).

Output: per-op device ms/epoch for the top ops, tagged with what each op
is (gather / scatter-apply / plan sort / cummax / bookkeeping), plus the
roofline summary BASELINE.md quotes:

* the epoch is RANDOM-ACCESS bound: the read gather and the winner
  scatter-apply are per-index limited (~7.1 / ~4.9 ns per lane on v5e —
  XLA's TPU gather/scatter primitive rate, invariant across 9 tested
  formulations: 1D/2D-row layouts, sorted/unique hints, OOB-drop
  steering, one-hot-matmul hot paths, compaction via second sorts), and
* the sum of the irreducible primitives (gather + scatter + plan sort)
  is reported as a fraction of the epoch — the "% of primitive roofline"
  figure.  The absolute HBM roofline (two 655k-lane passes at 32 B
  transaction granularity = ~42 MB = ~51 us at 819 GB/s) is ~150x away
  and unreachable without per-lane dynamic addressing, which neither XLA
  nor Mosaic/Pallas exposes on v5e.

Usage:
    python tools/roofline.py [--full-row] [--eb 65536] [--epochs 20]
"""

from __future__ import annotations

import argparse
import collections
import glob
import gzip
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


# op-name prefix -> phase attribution at the headline shape (v5e HLO);
# anything unmatched lands in "bookkeeping/other"
def classify(name: str, big: dict) -> str:
    if name.startswith("sort."):
        return "plan sort (key,rank,w)"
    if name.startswith("reduce-window"):
        return "mono-scatter cummax"
    if name.startswith("fusion."):
        # the two dominant fusions are the RA passes: larger = gather
        # (it also folds the forwarded-value where + checksum), smaller =
        # scatter apply.  Identified by rank among fusions, checked
        # against metadata when present.
        return big.get(name, "bookkeeping/other")
    return "bookkeeping/other"


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--full-row", action="store_true")
    ap.add_argument("--eb", type=int, default=65536)
    ap.add_argument("--epochs", type=int, default=20)
    args = ap.parse_args()

    import jax

    from deneva_tpu.config import Config
    from deneva_tpu.engine.step import Engine
    from deneva_tpu.workloads import get_workload

    eb = args.eb
    table = (1 << 21) if args.full_row else (1 << 23)
    over = ["--sim_full_row=true"] if args.full_row else []
    cfg = Config.from_args([
        "--workload=YCSB", "--cc_alg=TPU_BATCH", "--zipf_theta=0.9",
        "--read_perc=0.5", "--write_perc=0.5", "--req_per_query=10",
        "--max_accesses=16", f"--synth_table_size={table}",
        f"--epoch_batch={eb}", f"--max_txn_in_flight={eb}",
    ] + over)
    wl = get_workload(cfg)
    eng = Engine(cfg, wl)
    state = eng.init_state()
    n = args.epochs
    run = eng.jit_run
    state = run(state, n)
    jax.block_until_ready(state.stats["total_txn_commit_cnt"])

    tmp = tempfile.mkdtemp(prefix="roofline_")
    with jax.profiler.trace(tmp):
        state = run(state, n)
        jax.block_until_ready(state.stats["total_txn_commit_cnt"])

    path = sorted(glob.glob(os.path.join(
        tmp, "plugins/profile/*/*.trace.json.gz")))[-1]
    with gzip.open(path) as f:
        tr = json.load(f)
    pids = {}
    for e in tr["traceEvents"]:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            pids[e["pid"]] = e["args"].get("name", "")
    by = collections.Counter()
    for e in tr["traceEvents"]:
        if e.get("ph") == "X" and "TPU" in pids.get(e["pid"], ""):
            nm = e["name"]
            if nm.startswith(("jit_", "while")):
                by["__total__"] = max(by["__total__"], e.get("dur", 0))
                continue
            by[nm] += e.get("dur", 0)

    if "__total__" not in by or by["__total__"] == 0:
        sys.exit("roofline: no top-level jit_/while event found on the "
                 "TPU trace track — profiler naming changed? inspect "
                 f"{path} by hand")
    total = by.pop("__total__") / n / 1000
    # label the two biggest fusions as the RA passes
    fus = sorted(((d, nm) for nm, d in by.items()
                  if nm.startswith("fusion.")), reverse=True)
    big = {}
    if len(fus) >= 2 and fus[1][0] / n / 1000 > 0.2 * total:
        big[fus[0][1]] = "exec read gather (+fwd where +checksum)"
        big[fus[1][1]] = "exec winner scatter apply"
    else:
        print("WARNING: fusion-labeling heuristic failed at this shape "
              "(the two RA passes were not the two dominant fusions); "
              "per-index rates below are NOT computed", file=sys.stderr)

    mode = "full-row" if args.full_row else "fingerprint"
    print(f"# roofline ledger: eb={eb} x {cfg.req_per_query} req = "
          f"{eb * cfg.req_per_query} lanes, table {table} rows, {mode}")
    print(f"device epoch: {total:.3f} ms -> "
          f"{eb / total * 1000 / 1e6:.2f}M txn/s (device-bound)\n")
    phases = collections.Counter()
    for nm, d in by.items():
        phases[classify(nm, big)] += d
    print(f"{'phase':<42}{'ms/epoch':>9}{'% epoch':>9}")
    for ph, d in phases.most_common():
        ms = d / n / 1000
        print(f"{ph:<42}{ms:>9.3f}{100 * ms / total:>8.1f}%")
    if not big:
        return
    lanes = eb * cfg.req_per_query
    g = next((d for nm, d in by.items()
              if big.get(nm, "").startswith("exec read")), 0) / n / 1000
    s = next((d for nm, d in by.items()
              if big.get(nm, "").startswith("exec winner")), 0) / n / 1000
    srt = sum(d for nm, d in by.items()
              if nm.startswith("sort.")) / n / 1000
    prim = g + s + srt
    print(f"\nper-index rates: gather {g * 1e6 / lanes:.1f} ns/lane, "
          f"scatter {s * 1e6 / lanes:.1f} ns/lane "
          f"({lanes} lanes)")
    print(f"irreducible primitives (gather+scatter+sort): {prim:.3f} ms "
          f"= {100 * prim / total:.0f}% of epoch "
          f"(residue {total - prim:.3f} ms bookkeeping)")


if __name__ == "__main__":
    main()
