"""Final round-2 rerun set: points invalidated mid-campaign.

* TIMESTAMP / MVCC sweep points re-measured with the widened watermark
  tables (the first pass ran before the false-sharing fix);
* the escrow-ablation points that failed during a TPU worker restart.
"""

from __future__ import annotations

import sys

sys.path.insert(0, "/root/repo")

from deneva_tpu.config import CCAlg  # noqa: E402
from deneva_tpu.harness.experiments import get_experiment, paper_base  # noqa: E402
from deneva_tpu.harness.run import run_point  # noqa: E402


def bench(cfgs):
    return [c.replace(warmup_secs=1.5, done_secs=4.0) for c in cfgs]


def main() -> int:
    jobs = []
    to_algs = (CCAlg.TIMESTAMP, CCAlg.MVCC)
    jobs.append(("ycsb_skew", bench(
        [c for c in get_experiment("ycsb_skew", quick=False)
         if c.cc_alg in to_algs])))
    jobs.append(("operating_points", bench(
        [c for c in get_experiment("operating_points", quick=False)
         if c.cc_alg in to_algs])))
    base = paper_base(False)
    tpcc = base.replace(workload="TPCC", max_accesses=32, num_wh=64,
                        epoch_batch=2048, exec_subrounds=2)
    jobs.append(("escrow_ablation", bench([
        tpcc.replace(cc_alg=CCAlg.TPU_BATCH, escrow_order_free=False),
        tpcc.replace(cc_alg=CCAlg.CALVIN, escrow_order_free=False),
    ])))
    pps = base.replace(workload="PPS", max_accesses=32, epoch_batch=1024,
                       exec_subrounds=4)
    jobs.append(("escrow_ablation", bench([
        pps.replace(cc_alg=CCAlg.CALVIN, escrow_order_free=True),
    ])))
    for name, cfgs in jobs:
        print(f"[{name}] {len(cfgs)} points", flush=True)
        for cfg in cfgs:
            run_point(cfg, f"results/{name}", quiet=False)
    print("CAMPAIGN_C_DONE", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
