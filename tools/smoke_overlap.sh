#!/usr/bin/env bash
# Host-pipeline smoke gate (smoke_chaos.sh-style timed gate): the
# overlap-on and overlap-off cluster runs must produce bit-identical
# command logs / replica streams / state digests / acked-tag sets
# (tests/test_runtime.py::test_host_overlap_bit_identical), the
# zero-copy codec paths must stay byte-identical to the bytes codecs
# (tests/test_wire_zero_copy.py), and tools/wirebench.py must show the
# >= 2x dispatch-thread critical-path reduction the PR's acceptance
# names (wirebench exits nonzero below the bar).
#
# Usage: tools/smoke_overlap.sh     (OVERLAP_TIMEOUT_SECS to override)
set -euo pipefail
cd "$(dirname "$0")/.."

HARD_TIMEOUT="${OVERLAP_TIMEOUT_SECS:-600}"

timeout -k 10 "$HARD_TIMEOUT" \
    env JAX_PLATFORMS=cpu \
    python -m pytest tests/test_wire_zero_copy.py \
    "tests/test_runtime.py::test_host_overlap_bit_identical" \
    -q -p no:cacheprovider

exec timeout -k 10 "$HARD_TIMEOUT" \
    env JAX_PLATFORMS=cpu \
    python tools/wirebench.py --out /tmp/wirebench_smoke
