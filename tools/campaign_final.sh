#!/bin/bash
# Final round-2 measurement chain (sequential: single-client TPU tunnel).
cd /root/repo
set -ex
python tools/campaign_r2c.py                  # post-fix T/O reruns + escrow reruns
python tools/measure_cluster_tpu.py           # cluster-mode on the chip
python bench.py > /tmp/bench_final.json 2>/tmp/bench_final.err
python tools/campaign_r2b.py writes
python tools/campaign_r2b.py tpcc
python tools/campaign_r2b.py pps modes
echo CAMPAIGN_FINAL_DONE
