#!/usr/bin/env bash
# Shared smoke-gate runner: ONE timeout/reporting path for every timed
# gate (the former smoke_chaos.sh / smoke_escrow.sh / smoke_overlap.sh
# are now thin delegates into this script).
#
#   tools/smoke.sh chaos [scenario ...]   chaos harness (default lossy-net)
#   tools/smoke.sh escrow                 TPC-C escrow floor gate
#   tools/smoke.sh overlap                host-pipeline bit-identity + wirebench
#   tools/smoke.sh elastic                membership gate: elastic-grow /
#                                         elastic-drain / elastic-kill-reassign
#                                         (liveness + exactly-once invariants)
#   tools/smoke.sh geo                    geo-replication gate: region-loss /
#                                         asymmetric-WAN / replica-lag
#                                         (quorum commit, follower snapshot
#                                         reads, promote-on-region-loss)
#   tools/smoke.sh overload               overload-robustness gate:
#                                         flash-crowd / aggressor-tenant /
#                                         diurnal (bounded admission queue,
#                                         shed + recovery, tenant fairness,
#                                         exactly-once under NACK+resend)
#   tools/smoke.sh partition              partition-tolerance gate:
#                                         symmetric split / asymmetric
#                                         split / gray-slow node /
#                                         flapping link (fencing=true:
#                                         quorum reassignment, minority
#                                         self-fence exit 18, single-
#                                         writer-per-slot + digest-vs-
#                                         replay invariants)
#   tools/smoke.sh trace                  flight-recorder gate:
#                                         telemetry-off wire pin test
#                                         (bit-identity contract) + the
#                                         trace-kill chaos scenario
#                                         (telemetry=true across a
#                                         crash/recovery: every sampled
#                                         committed txn has a gap-free
#                                         client->admit->batch->verdict
#                                         ->quorum->ack span chain, the
#                                         merger renders one flow-linked
#                                         Chrome trace)
#   tools/smoke.sh monitor                metrics-bus gate:
#                                         metrics-off wire pin test
#                                         (bit-identity contract) + the
#                                         monitor-grayslow chaos
#                                         scenario (metrics=true with a
#                                         gray-slow peer + an aggregator
#                                         fault_kill: straggler watchdog
#                                         names the stalled node, the
#                                         recovered aggregator resumes
#                                         the metrics_bus stream)
#   tools/smoke.sh audit                  isolation-audit gate:
#                                         audit-off bit-identity tests
#                                         (no sidecar, pre-audit group
#                                         arity, armed==off row state)
#                                         + the audit-clean /
#                                         audit-mutation chaos pair
#                                         (contended OCC certifies
#                                         serializable; the seeded
#                                         occ-read-skip mutation is
#                                         REJECTED with a cycle witness
#                                         naming the mutated epoch)
#   tools/smoke.sh ctrl                   control-plane gate:
#                                         ctrl-off bit-identity tests
#                                         (no controller object, static
#                                         knobs ≡ legacy path) + the
#                                         ctrl-shift-degrade chaos
#                                         scenario (zipf 0→0.9 mid-run
#                                         shift + flash crowd + an
#                                         aggregator fault_kill: armed
#                                         decisions adapt the backend
#                                         map, the governor falls back
#                                         to static on signal loss and
#                                         re-engages after heal, every
#                                         decision stream replays
#                                         bit-for-bit, exactly-once +
#                                         digest-vs-replay + audit
#                                         certificate all green)
#   tools/smoke.sh repair                 transaction-repair gate:
#                                         repair-contention (zipf-0.9
#                                         write-heavy OCC with repair on +
#                                         crash/recovery: exactly-once with
#                                         salvaged txns acked as commits,
#                                         bit-identical replay through the
#                                         repair sub-rounds, salvage > 0)
#   tools/smoke.sh mesh                   pod-scale measured-path gate:
#                                         the dp=8-vs-dp=1 bit-identity
#                                         oracle (cluster verdict planes,
#                                         logs, acks and replay digests
#                                         identical across the mesh axis,
#                                         YCSB + TPC-C) + the 8-virtual-
#                                         device multichip dry run
#                                         (sharded compile + measured-path
#                                         run_simulation over every
#                                         backend family)
#   tools/smoke.sh dgcc                   wavefront-backend gate:
#                                         dgcc-off pin tests (router/
#                                         map/counter/wire bit-identity
#                                         with the backend unarmed) +
#                                         the zipf-0.9 write-heavy
#                                         anti-inert window (waves
#                                         chain: wave_max > 1,
#                                         waves > epochs, commits > 0,
#                                         aborts == 0)
#   tools/smoke.sh lint                   static-analysis gate: graftlint v2
#                                         (trace/det/wire/own/imports + the
#                                         gate/life/jit families on the
#                                         CFG core) + ruff (pyflakes slice,
#                                         when installed) over deneva_tpu/ +
#                                         tools/.  `lint --changed` = the
#                                         git-diff-scoped incremental mode
#                                         (fast pre-commit signal; the
#                                         full-tree run stays the gate)
#
# Timeout: SMOKE_TIMEOUT_SECS overrides for any scenario; the legacy
# per-gate envs (CHAOS_TIMEOUT_SECS, ESCROW_TIMEOUT_SECS,
# OVERLAP_TIMEOUT_SECS, ELASTIC_TIMEOUT_SECS) still win when set.
# Exits nonzero on an invariant violation, a node error, or the timeout.
set -euo pipefail
cd "$(dirname "$0")/.."

SCEN="${1:-}"
[ $# -gt 0 ] && shift

run() {
    local t="$1"; shift
    timeout -k 10 "$t" env JAX_PLATFORMS=cpu "$@"
}

case "$SCEN" in
  chaos)
    T="${SMOKE_TIMEOUT_SECS:-${CHAOS_TIMEOUT_SECS:-300}}"
    run "$T" python -m deneva_tpu.harness.chaos "${@:-lossy-net}" --quick
    ;;
  escrow)
    T="${SMOKE_TIMEOUT_SECS:-${ESCROW_TIMEOUT_SECS:-600}}"
    run "$T" python -m pytest \
        tests/test_escrow.py::test_tpcc_escrow_smoke_above_floor \
        -q -p no:cacheprovider
    ;;
  overlap)
    T="${SMOKE_TIMEOUT_SECS:-${OVERLAP_TIMEOUT_SECS:-600}}"
    run "$T" python -m pytest tests/test_wire_zero_copy.py \
        "tests/test_runtime.py::test_host_overlap_bit_identical" \
        -q -p no:cacheprovider
    run "$T" python tools/wirebench.py --out /tmp/wirebench_smoke
    ;;
  elastic)
    T="${SMOKE_TIMEOUT_SECS:-${ELASTIC_TIMEOUT_SECS:-600}}"
    run "$T" python -m deneva_tpu.harness.chaos elastic --quick
    ;;
  geo)
    T="${SMOKE_TIMEOUT_SECS:-${GEO_TIMEOUT_SECS:-900}}"
    run "$T" python -m deneva_tpu.harness.chaos geo --quick
    ;;
  overload)
    T="${SMOKE_TIMEOUT_SECS:-${OVERLOAD_TIMEOUT_SECS:-900}}"
    run "$T" python -m deneva_tpu.harness.chaos overload --quick
    ;;
  partition)
    # full done-windows even under --quick (the PR 4 clamped-window
    # lesson): the fault fires ~3 s in, suspicion needs its silence
    # floor, and the takeover replay-jit stall runs 4-5 s on the CI box
    T="${SMOKE_TIMEOUT_SECS:-${PARTITION_TIMEOUT_SECS:-900}}"
    run "$T" python -m deneva_tpu.harness.chaos partition --quick
    ;;
  repair)
    T="${SMOKE_TIMEOUT_SECS:-${REPAIR_TIMEOUT_SECS:-600}}"
    run "$T" python -m deneva_tpu.harness.chaos repair-contention --quick
    ;;
  ctrl)
    # off-pin first (fast, in-process engine); then the shift/flash/
    # kill scenario — it reuses the kill-one-server recovery machinery
    # plus a governor trip + heal window, so partition-family budget
    T="${SMOKE_TIMEOUT_SECS:-${CTRL_TIMEOUT_SECS:-900}}"
    run "$T" python -m pytest \
        "tests/test_ctrl.py::test_ctrl_off_wire_pin" \
        "tests/test_ctrl.py::test_ctrl_off_knobs_value_identity" \
        -q -p no:cacheprovider
    run "$T" python -m deneva_tpu.harness.chaos ctrl --quick
    ;;
  audit)
    # off-pin first (fast, loopback + in-process engine), then the
    # certify-clean / catch-the-mutation chaos pair
    T="${SMOKE_TIMEOUT_SECS:-${AUDIT_TIMEOUT_SECS:-600}}"
    run "$T" python -m pytest \
        "tests/test_audit.py::test_audit_off_group_outputs" \
        "tests/test_audit.py::test_audit_observation_only_row_state" \
        -q -p no:cacheprovider
    run "$T" python -m deneva_tpu.harness.chaos audit --quick
    ;;
  monitor)
    # off-pin first (fast, loopback); then the gray-slow + aggregator-
    # kill scenario — the kill-one-server recovery machinery plus the
    # stall, so it gets the partition-family budget
    T="${SMOKE_TIMEOUT_SECS:-${MONITOR_TIMEOUT_SECS:-900}}"
    run "$T" python -m pytest \
        "tests/test_metricsbus.py::test_metrics_off_wire_pin" \
        "tests/test_metricsbus.py::test_metrics_off_group_outputs" \
        -q -p no:cacheprovider
    run "$T" python -m deneva_tpu.harness.chaos monitor-grayslow --quick
    ;;
  trace)
    # the off-pin half is fast (loopback ServerNode + ClientNode, no
    # cluster); the chaos half reuses the kill-one-server recovery
    # machinery, so it gets the same budget as the repair gate
    T="${SMOKE_TIMEOUT_SECS:-${TRACE_TIMEOUT_SECS:-600}}"
    run "$T" python -m pytest \
        "tests/test_telemetry.py::test_telemetry_off_wire_pin" \
        "tests/test_telemetry.py::test_telemetry_off_client_pin" \
        -q -p no:cacheprovider
    run "$T" python -m deneva_tpu.harness.chaos trace-kill --quick
    ;;
  mesh)
    # oracle first: the dp=8 cluster reproduces dp=1 bit-for-bit
    # (verdict planes, logs, acks, replay digests; YCSB + TPC-C), then
    # the multichip dry run — sharded compile over every backend family
    # plus the measured-path run_simulation window.  Both need the 8
    # forced host devices BEFORE jax initializes.
    T="${SMOKE_TIMEOUT_SECS:-${MESH_TIMEOUT_SECS:-900}}"
    run "$T" env XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python -m pytest tests/test_mesh_cluster.py -q -p no:cacheprovider
    run "$T" env XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python -c "import __graft_entry__ as g; g.dryrun_multichip(8)"
    ;;
  dgcc)
    # off-pin first (router candidates / backend map / device counters /
    # wire bytes all pre-DGCC with the backend unarmed), then the
    # anti-inert half through the REAL measured path: a zipf-0.9
    # write-heavy window where the wavefront must actually chain
    # (wave_max > 1, waves > epochs) while committing with ZERO aborts —
    # the near-zero-abort claim, pinned (a run that silently stopped
    # validating would fail the commit floor, one that stopped chaining
    # would fail wave_max)
    T="${SMOKE_TIMEOUT_SECS:-${DGCC_TIMEOUT_SECS:-600}}"
    run "$T" python -m pytest \
        "tests/test_dgcc.py::test_dgcc_off_pin" \
        "tests/test_dgcc.py::test_engine_hot_zipf_waves_chain_zero_aborts" \
        -q -p no:cacheprovider
    run "$T" python - <<'EOF'
from deneva_tpu.config import CCAlg, Config
from deneva_tpu.engine.driver import run_simulation

cfg = Config(cc_alg=CCAlg.DGCC, zipf_theta=0.9,
             read_perc=0.1, write_perc=0.9,
             synth_table_size=1 << 14, req_per_query=8, max_accesses=8,
             epoch_batch=512, conflict_buckets=2048,
             max_txn_in_flight=2048,
             warmup_secs=0.5, done_secs=2.0).validate()
st = run_simulation(cfg)
c = st.counters
epochs, commits = c["epoch_cnt"], c["total_txn_commit_cnt"]
aborts, waves = c["total_txn_abort_cnt"], c["dgcc_wave_cnt"]
wave_max = c["dgcc_wave_max"]
print(f"[dgcc-smoke] epochs={epochs:.0f} commits={commits:.0f} "
      f"aborts={aborts:.0f} waves={waves:.0f} wave_max={wave_max:.0f} "
      f"fallback={c['dgcc_fallback_cnt']:.0f} "
      f"edges={c['dgcc_edge_cnt']:.0f}")
assert commits > 0, "inert: nothing committed"
assert aborts == 0, f"DGCC aborted {aborts:.0f} txns"
assert wave_max > 1, "inert: wavefront never chained"
assert waves > epochs, "inert: ~1 wave per epoch at zipf 0.9"
print("[dgcc-smoke] PASS")
EOF
    ;;
  lint)
    # static gate; budget 30 s total on the 2-core CI box (graftlint v2
    # measures ~6.5 s full-tree over the 8 families / 78 files, ruff
    # sub-second).  `tools/smoke.sh lint --changed` runs the git-diff-
    # scoped incremental mode instead (~2 s, pre-commit feedback);
    # cross-file families see only the subset there, so the FULL-tree
    # run stays the gate CI must pass.
    T="${SMOKE_TIMEOUT_SECS:-${LINT_TIMEOUT_SECS:-30}}"
    if [ "${1:-}" = "--changed" ]; then
        run "$T" python -m tools.graftlint --changed deneva_tpu/ tools/
    else
        run "$T" python -m tools.graftlint deneva_tpu/ tools/
    fi
    if command -v ruff >/dev/null 2>&1; then
        # generic pyflakes + import-hygiene baseline (ruff.toml); boxes
        # without ruff still get graftlint's imports family
        run "$T" ruff check deneva_tpu tools tests
    else
        echo "[lint] ruff not installed; graftlint imports family stands in"
    fi
    ;;
  *)
    echo "usage: tools/smoke.sh <chaos|escrow|overlap|elastic|geo|overload|partition|repair|ctrl|monitor|trace|mesh|dgcc|lint> [args...]" >&2
    exit 2
    ;;
esac
