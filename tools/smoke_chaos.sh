#!/usr/bin/env bash
# Delegate kept for back-compat: the shared runner is tools/smoke.sh.
exec "$(dirname "$0")/smoke.sh" chaos "$@"
