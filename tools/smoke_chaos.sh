#!/usr/bin/env bash
# Chaos smoke gate: a 2-server + 1-client IPC cluster must survive the
# seeded lossy-net scenario (drops on the open-loop traffic, client
# resend + server idempotent admission repairing them) under a hard
# timeout — the liveness property the reference never had (SURVEY §5.3:
# a dead/lossy link hangs it forever).
#
# Usage: tools/smoke_chaos.sh [scenario ...]   (default: lossy-net)
# Exits nonzero on an invariant violation, a node error, or the timeout.
set -euo pipefail
cd "$(dirname "$0")/.."

SCENARIOS=("${@:-lossy-net}")
HARD_TIMEOUT="${CHAOS_TIMEOUT_SECS:-300}"

exec timeout -k 10 "$HARD_TIMEOUT" \
    env JAX_PLATFORMS=cpu \
    python -m deneva_tpu.harness.chaos "${SCENARIOS[@]}" --quick
