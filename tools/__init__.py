# tools/ is an importable package so `python -m tools.graftlint` works
# from the repo root (the same way the harness modules run with -m).
