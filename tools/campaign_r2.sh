#!/bin/bash
# Round-2 TPU measurement campaign: sequential (single-client tunnel).
cd /root/repo
set -x
python tools/measure_cluster_tpu.py
for exp in isolation_levels operating_points escrow_ablation ycsb_skew \
           ycsb_writes pps_scaling tpcc_scaling ycsb_inflight modes; do
  timeout 5400 python -m deneva_tpu.harness.run $exp --bench
done
echo CAMPAIGN_DONE
