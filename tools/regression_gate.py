"""Throughput + measurement-health regression gate over committed sweep
results.

Usage:
  python tools/regression_gate.py capture   # results/ -> results/expected.json
  python tools/regression_gate.py check     # fail if tput regressed
  python tools/regression_gate.py check --no-runtime   # tput only

``check`` compares every point present in both the live results tree and
the committed expectation table; a point regresses when its measured
tput falls below ``(1 - tolerance)`` of the expectation.  Missing points
warn (sweeps are allowed to grow); new points pass.  This is the
round-over-round guard VERDICT round-1 #10 asked for: a later round can
diff numbers instead of trusting prose.

``check`` additionally validates MEASUREMENT HEALTH (VERDICT round-5
weak #3 / next #4): a point whose ``total_runtime`` exceeds
``RUNTIME_FACTOR x`` its configured bench window (the ``done_secs`` the
file's own `# cfg` echo records) is STARVED — the host wedged or was
descheduled mid-window, so its tput is an artifact, not a measurement
(the shipped ycsb_inflight NO_WAIT@TIF=10000 point ran 70s against a 4s
window and passed the old tput-only gate).  Starved points fail the
gate regardless of their tput; re-run them via tools/rerun_starved.py
or drop them.

Tolerance default 0.35: single-chip tunnel runs show up to ~20 % run
variance; the gate is for catching collapses (algorithmic regressions,
accidental de-tuning), not 5 % noise.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deneva_tpu.harness.parse import load_results  # noqa: E402

EXPECTED = "results/expected.json"
SWEEPS = ("isolation_levels", "operating_points", "escrow_ablation",
          "ycsb_skew", "ycsb_writes", "ycsb_hot", "ycsb_inflight",
          "ycsb_scaling", "ycsb_partitions",
          "tpcc_scaling", "tpcc_escrow", "pps_scaling", "modes",
          "cluster_tpu", "cluster_scaling", "network_sweep")
# a measured window may overrun its spec this much (host pacing jitter +
# the final partial chunk) before the point counts as starved
RUNTIME_FACTOR = 2.0
RUNTIME_SLACK_SECS = 2.0

# instrument-overhead gates: each <preset>_on.out / <preset>_off.out
# pair — same preset, the instrument armed at its default depth knob vs
# off — must show the armed run's tput within the tolerance of the off
# run's, AND the armed run must prove the instrument was LIVE via its
# anti-inert field (a gate that passes with the instrument dead proves
# nothing).  tools/telemetry_bench.py writes the telemetry pairs
# (flight recorder at telemetry_sample=1024); tools/metricsbus_bench.py
# the metricsbus pairs (live bus at metrics_cadence=1);
# tools/audit_bench.py the audit pairs (serializability certifier at
# audit_cadence=1 — its anti-inert field additionally requires
# audit_edges_dropped == 0, an incomplete certificate being as dead as
# an inert one).
TELEMETRY_DIR = "results/telemetry"
METRICSBUS_DIR = "results/metricsbus"
AUDIT_DIR = "results/audit"
TELEMETRY_TOLERANCE = 0.02


def live_table() -> dict[str, float]:
    out: dict[str, float] = {}
    for exp in SWEEPS:
        d = os.path.join("results", exp)
        if not os.path.isdir(d):
            continue
        for row in load_results(d):
            if "tput" in row:
                out[f"{exp}/{row['file']}"] = float(row["tput"])
    return out


def runtime_violations() -> list[tuple[str, float, float]]:
    """(point, total_runtime, window) for every live point whose measured
    window overran its own configured ``done_secs`` spec."""
    out = []
    for exp in SWEEPS:
        d = os.path.join("results", exp)
        if not os.path.isdir(d):
            continue
        for row in load_results(d):
            rt, win = row.get("total_runtime"), row.get("done_secs")
            if rt is None or not win:
                continue
            if float(rt) > RUNTIME_FACTOR * float(win) + RUNTIME_SLACK_SECS:
                out.append((f"{exp}/{row['file']}", float(rt), float(win)))
    return out


def _pair_violations(pair_dir: str, label: str, inert_field: str,
                     zero_field: str | None) -> list[str]:
    """One instrument's anti-inert + anti-regression pass: for every
    ``<preset>_on.out``, its ``_off`` twin must exist, the armed run
    must prove liveness (``inert_field`` > 0, ``zero_field`` == 0 when
    declared), and armed tput must stay within TELEMETRY_TOLERANCE of
    off."""
    out: list[str] = []
    if not os.path.isdir(pair_dir):
        return out
    rows = {r["file"]: r for r in load_results(pair_dir)}
    for name, row in sorted(rows.items()):
        if not name.endswith("_on.out"):
            continue
        off = rows.get(name[:-len("_on.out")] + "_off.out")
        if off is None:
            out.append(f"{name}: missing its _off.out twin")
            continue
        if "tput" not in off:
            out.append(f"{name}: its _off.out twin has no tput "
                       "(malformed [summary]?)")
            continue
        if row.get(inert_field, 0.0) <= 0:
            out.append(f"{name}: {inert_field} == 0 — the {label} "
                       "instrument was INERT in the armed run")
        if zero_field is not None and row.get(zero_field, 0.0) > 0:
            out.append(f"{name}: {zero_field} = "
                       f"{row[zero_field]:.0f} (must be 0)")
        if "tput" not in row:
            out.append(f"{name}: no tput in the armed run")
            continue
        floor = (1.0 - TELEMETRY_TOLERANCE) * float(off["tput"])
        if float(row["tput"]) < floor:
            out.append(
                f"{name}: {label} overhead exceeds "
                f"{TELEMETRY_TOLERANCE:.0%}: armed tput "
                f"{row['tput']:.0f} < {floor:.0f} "
                f"(off {off['tput']:.0f})")
    return out


def telemetry_violations() -> list[str]:
    """Anti-inert + anti-regression over every committed instrument
    pair family (flight recorder + metrics bus + isolation audit).
    The dirs resolve at call time so tests can repoint them."""
    pairs = (
        # (dir, label, anti-inert field, zero-required field or None)
        (TELEMETRY_DIR, "telemetry", "tel_sampled_cnt",
         "tel_dropped_cnt"),
        (METRICSBUS_DIR, "metricsbus", "mb_frames_sent", None),
        (AUDIT_DIR, "audit", "audit_edges_exported",
         "audit_edges_dropped"),
    )
    out: list[str] = []
    for pair_dir, label, inert_field, zero_field in pairs:
        out += _pair_violations(pair_dir, label, inert_field, zero_field)
    return out


def capture() -> int:
    table = live_table()
    # never bake a starved artifact into the baseline: a 70s-window tput
    # as the expectation would later flag the honest re-measurement as a
    # false REGRESSION (and mask real ones until recapture)
    starved = {key for key, _rt, _win in runtime_violations()}
    for key in sorted(starved & table.keys()):
        print(f"capture: skipping STARVED {key} (re-run it first)")
        del table[key]
    with open(EXPECTED, "w") as f:
        json.dump(dict(sorted(table.items())), f, indent=1)
    print(f"captured {len(table)} points -> {EXPECTED}")
    return 0


def check(tolerance: float = 0.35, runtime: bool = True) -> int:
    if not os.path.exists(EXPECTED):
        print(f"no {EXPECTED}; run `capture` first")
        return 2
    with open(EXPECTED) as f:
        expected = json.load(f)
    live = live_table()
    bad, missing = [], []
    for key, want in expected.items():
        got = live.get(key)
        if got is None:
            missing.append(key)
        elif got < want * (1.0 - tolerance):
            bad.append((key, want, got))
    for key, want, got in bad:
        print(f"REGRESSION {key}: expected >= {want * (1 - tolerance):.0f} "
              f"(baseline {want:.0f}), got {got:.0f}")
    starved = runtime_violations() if runtime else []
    for key, rt, win in starved:
        print(f"STARVED {key}: total_runtime={rt:.1f}s against a "
              f"{win:.1f}s window (> {RUNTIME_FACTOR:g}x + "
              f"{RUNTIME_SLACK_SECS:g}s) — re-run via "
              f"tools/rerun_starved.py or drop the point")
    tel = telemetry_violations()
    for msg in tel:
        print(f"TELEMETRY {msg}")
    if missing:
        print(f"note: {len(missing)} expected points absent from this run")
    print(f"checked {len(expected) - len(missing)} points, "
          f"{len(bad)} regressions, {len(starved)} starved, "
          f"{len(tel)} telemetry violations")
    return 1 if bad or starved or tel else 0


if __name__ == "__main__":
    args = sys.argv[1:]
    cmd = args[0] if args else "check"
    sys.exit(capture() if cmd == "capture"
             else check(runtime="--no-runtime" not in args))
