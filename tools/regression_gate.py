"""Throughput regression gate over committed sweep results.

Usage:
  python tools/regression_gate.py capture   # results/ -> results/expected.json
  python tools/regression_gate.py check     # fail if tput regressed

``check`` compares every point present in both the live results tree and
the committed expectation table; a point regresses when its measured
tput falls below ``(1 - tolerance)`` of the expectation.  Missing points
warn (sweeps are allowed to grow); new points pass.  This is the
round-over-round guard VERDICT round-1 #10 asked for: a later round can
diff numbers instead of trusting prose.

Tolerance default 0.35: single-chip tunnel runs show up to ~20 % run
variance; the gate is for catching collapses (algorithmic regressions,
accidental de-tuning), not 5 % noise.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deneva_tpu.harness.parse import load_results  # noqa: E402

EXPECTED = "results/expected.json"
SWEEPS = ("isolation_levels", "operating_points", "escrow_ablation",
          "ycsb_skew", "ycsb_writes", "ycsb_hot", "ycsb_inflight",
          "ycsb_scaling", "ycsb_partitions",
          "tpcc_scaling", "pps_scaling", "modes", "cluster_tpu",
          "cluster_scaling", "network_sweep")


def live_table() -> dict[str, float]:
    out: dict[str, float] = {}
    for exp in SWEEPS:
        d = os.path.join("results", exp)
        if not os.path.isdir(d):
            continue
        for row in load_results(d):
            if "tput" in row:
                out[f"{exp}/{row['file']}"] = float(row["tput"])
    return out


def capture() -> int:
    table = live_table()
    with open(EXPECTED, "w") as f:
        json.dump(dict(sorted(table.items())), f, indent=1)
    print(f"captured {len(table)} points -> {EXPECTED}")
    return 0


def check(tolerance: float = 0.35) -> int:
    if not os.path.exists(EXPECTED):
        print(f"no {EXPECTED}; run `capture` first")
        return 2
    with open(EXPECTED) as f:
        expected = json.load(f)
    live = live_table()
    bad, missing = [], []
    for key, want in expected.items():
        got = live.get(key)
        if got is None:
            missing.append(key)
        elif got < want * (1.0 - tolerance):
            bad.append((key, want, got))
    for key, want, got in bad:
        print(f"REGRESSION {key}: expected >= {want * (1 - tolerance):.0f} "
              f"(baseline {want:.0f}), got {got:.0f}")
    if missing:
        print(f"note: {len(missing)} expected points absent from this run")
    print(f"checked {len(expected) - len(missing)} points, "
          f"{len(bad)} regressions")
    return 1 if bad else 0


if __name__ == "__main__":
    cmd = sys.argv[1] if len(sys.argv) > 1 else "check"
    sys.exit(capture() if cmd == "capture" else check())
