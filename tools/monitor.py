"""Live cluster monitor: tail a metrics-bus stream as a per-node TUI.

The metrics bus (runtime/metricsbus.py, ``metrics=true``) aggregates
every node's per-epoch frames into ``metrics_bus_node*.jsonl`` on the
lowest-id live server.  This tool renders that stream:

  python tools/monitor.py <stream.jsonl | run-dir>            live TUI
  python tools/monitor.py <stream.jsonl | run-dir> --once     one render
  python tools/monitor.py <stream.jsonl | run-dir> --prom     one-shot
                                       Prometheus text exposition dump

TUI columns (per node, from each node's most recent frames):
epoch, commit/s over the tail window, abort fraction, retry/admission
queue depths, the critical-path gate stage (argmax of the last [crit]
window), and the per-partition conflict density of the latest frame.
``[watch]`` events (epoch-stall / straggler / jit-recompile) render as
a scrolling event pane under the table.

Everything reads through the SHARED schema module
(runtime/metricschema.read_metrics), so a recovered aggregator's
appended stream (torn line mid-file) renders fine.
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deneva_tpu.runtime.metricschema import read_metrics  # noqa: E402

# frames participating in the rate window (per node)
TAIL = 32

# gauge fields exported to Prometheus (frame field -> metric suffix)
PROM_GAUGES = (
    ("commit", "commit_per_frame"),
    ("abort", "abort_per_frame"),
    ("defer", "defer_per_frame"),
    ("salvage", "salvage_per_frame"),
    ("shed", "shed_per_frame"),
    ("pending", "pending_depth"),
    ("retry_depth", "retry_depth"),
    ("held_rsp", "held_rsp_depth"),
    ("adm_depth", "admission_depth"),
    ("quorum_ms", "quorum_hold_ms"),
    ("resend", "resend_per_frame"),
    ("backoff", "backoff_per_frame"),
    ("backlog", "backlog_depth"),
    ("wall_ms", "critpath_wall_ms"),
)
PROM_STAGES = ("admit", "wire", "device", "retire", "other")


def split_rows(rows: list[dict]) -> tuple[dict[int, list[dict]],
                                          list[dict]]:
    """{node: [frames...]} (file order) + the [watch] event records."""
    frames: dict[int, list[dict]] = {}
    watches: list[dict] = []
    for r in rows:
        if "kind" in r:
            watches.append(r)
        elif "commit" in r:
            frames.setdefault(int(r.get("node", -1)), []).append(r)
    return frames, watches


def node_summary(frames: list[dict]) -> dict:
    """Rolled-up view of one node's frame tail."""
    tail = frames[-TAIL:]
    last = tail[-1]
    span_us = max(tail[-1].get("frame_t_us", 0)
                  - tail[0].get("frame_t_us", 0), 1)
    commits = sum(f.get("commit", 0.0) for f in tail)
    aborts = sum(f.get("abort", 0.0) for f in tail)
    stage_ms = {s: last.get(f"{s}_ms", 0.0) for s in PROM_STAGES}
    q = last.get("quorum_ms", 0.0)
    gate = max(stage_ms, key=stage_ms.get)
    if q > stage_ms[gate]:
        gate = "quorum"
    dens = last.get("density", [])
    return {
        "role": last.get("role", "?"),
        "epoch": int(last.get("epoch", -1)),
        "commit_s": commits / (span_us / 1e6) if len(tail) > 1 else 0.0,
        "abort_frac": aborts / max(commits + aborts, 1.0),
        "retry": int(last.get("retry_depth", 0)),
        "adm": int(last.get("adm_depth", 0)),
        "resend_s": sum(f.get("resend", 0.0) + f.get("backoff", 0.0)
                        for f in tail) / (span_us / 1e6)
        if len(tail) > 1 else 0.0,
        "gate": gate,
        "wall_ms": last.get("wall_ms", 0.0),
        "density": dens,
    }


def render_table(rows: list[dict], max_watch: int = 6) -> str:
    frames, watches = split_rows(rows)
    out = [f"{'node':>4} {'role':<7} {'epoch':>7} {'commit/s':>9} "
           f"{'abort%':>7} {'retry':>6} {'adm':>5} {'resend/s':>9} "
           f"{'gate':>7} {'wall_ms':>8}  density"]
    for node in sorted(frames):
        s = node_summary(frames[node])
        dens = ",".join(str(d) for d in s["density"][:8]) or "-"
        out.append(
            f"{node:>4} {s['role']:<7} {s['epoch']:>7} "
            f"{s['commit_s']:>9.0f} {s['abort_frac'] * 100:>6.1f}% "
            f"{s['retry']:>6} {s['adm']:>5} {s['resend_s']:>9.0f} "
            f"{s['gate']:>7} {s['wall_ms']:>8.1f}  {dens}")
    if not frames:
        out.append("  (no frames yet)")
    if watches:
        out.append("")
        out.append("watch events:")
        for w in watches[-max_watch:]:
            extra = " ".join(f"{k}={v}" for k, v in w.items()
                             if k not in ("kind", "subject", "node",
                                          "epoch", "t_us"))
            out.append(f"  [{w.get('kind')}] subject={w.get('subject')} "
                       f"epoch={w.get('epoch')} {extra}")
    return "\n".join(out)


def prom_dump(rows: list[dict]) -> str:
    """One-shot Prometheus text exposition of the latest cluster state
    (gauges from each node's newest frame + watch counters)."""
    frames, watches = split_rows(rows)
    out: list[str] = []

    def gauge(name: str, help_text: str, samples: list[tuple[str, float]]):
        out.append(f"# HELP deneva_{name} {help_text}")
        out.append(f"# TYPE deneva_{name} gauge")
        for labels, v in samples:
            out.append(f"deneva_{name}{{{labels}}} {v:g}")

    latest = {n: fr[-1] for n, fr in frames.items()}
    for field, suffix in PROM_GAUGES:
        gauge(suffix, f"metrics-bus frame field {field!r}",
              [(f'node="{n}",role="{f.get("role", "?")}"',
                float(f.get(field, 0.0)))
               for n, f in sorted(latest.items())])
    for s in PROM_STAGES:
        gauge(f"critpath_{s}_ms",
              f"critical-path {s} stage of the last window",
              [(f'node="{n}"', float(f.get(f"{s}_ms", 0.0)))
               for n, f in sorted(latest.items())
               if f.get("role") == "server"])
    dens_samples = []
    for n, f in sorted(latest.items()):
        for i, d in enumerate(f.get("density", [])):
            dens_samples.append((f'node="{n}",part="{i}"', float(d)))
    if dens_samples:
        gauge("conflict_density",
              "per-partition observed-conflict density (latest frame)",
              dens_samples)
    ctrl_nodes = {n: f for n, f in sorted(latest.items())
                  if f.get("ctrl_gov", 0.0) > 0}
    if ctrl_nodes:
        gauge("ctrl_gov", "controller governor (2=armed 1=static 0=off)",
              [(f'node="{n}"', float(f.get("ctrl_gov", 0.0)))
               for n, f in ctrl_nodes.items()])
        gauge("ctrl_quota_idx", "controller admission quota-scale rung",
              [(f'node="{n}"', float(f.get("ctrl_qidx", 0.0)))
               for n, f in ctrl_nodes.items()])
        gauge("ctrl_stale_trips", "governor trips to static on stale signals",
              [(f'node="{n}"', float(f.get("ctrl_trips", 0.0)))
               for n, f in ctrl_nodes.items()])
    counts: dict[str, int] = {}
    for w in watches:
        counts[str(w.get("kind"))] = counts.get(str(w.get("kind")), 0) + 1
    gauge("watch_events_total", "anomaly watchdog events by kind",
          [(f'kind="{k}"', float(v)) for k, v in sorted(counts.items())])
    return "\n".join(out) + "\n"


def render_ctrl(rows: list[dict]) -> str:
    """Controller panel: per-node governor state from the latest frame
    carrying live ``ctrl_*`` counters (runtime/server._mb_emit,
    ``ctrl=true``; gov encodes 0=off / 1=static / 2=armed).  Empty
    string when every frame reads gov=0 — the panel only appears on
    armed runs, so a ctrl-off stream renders byte-identically."""
    frames, _ = split_rows(rows)
    latest = {n: fr[-1] for n, fr in frames.items()
              if fr and fr[-1].get("ctrl_gov", 0.0) > 0}
    if not latest:
        return ""
    out = ["ctrl (feedback control plane):",
           f"{'node':>4} {'gov':>7} {'quota_scale':>12} {'trips':>6}"]
    for node in sorted(latest):
        f = latest[node]
        gov = "armed" if f.get("ctrl_gov", 0.0) >= 2 else "static"
        scale = 0.8 ** int(f.get("ctrl_qidx", 0))
        out.append(f"{node:>4} {gov:>7} {scale:>12.3f} "
                   f"{int(f.get('ctrl_trips', 0)):>6}")
    return "\n".join(out)


def load_audit_dir(path: str) -> dict[int, list[dict]]:
    """{node: audit records} of a run directory's isolation-audit
    sidecars (runtime/audit.py, ``audit=true``); {} when the plane is
    off or ``path`` is a bare stream file.  The sidecar discovery/
    parsing contract lives in ONE place — the certifier's loader."""
    if not os.path.isdir(path):
        return {}
    from deneva_tpu.harness.auditgraph import load_audit
    return load_audit(path)


def render_audit(by_node: dict[int, list[dict]]) -> str:
    """Latest per-node isolation-audit verdict: clean (zero dependency
    edges so far), edges observed (serializability judged by the
    offline certifier, harness/auditgraph.py), or export overflow."""
    out = ["audit (isolation):",
           f"{'node':>4} {'epoch':>7} {'epochs':>7} {'edges':>7} "
           f"{'dropped':>8}  verdict"]
    for node in sorted(by_node):
        recs = by_node[node]
        if not recs:
            continue
        last = recs[-1]
        edges = sum(int(r.get("edge_cnt", 0)) for r in recs)
        dropped = sum(int(r.get("dropped", 0)) for r in recs)
        verdict = "clean" if edges == 0 else "edges-observed"
        if dropped:
            verdict = "export-overflow"
        out.append(f"{node:>4} {int(last.get('epoch', -1)):>7} "
                   f"{len(recs):>7} {edges:>7} {dropped:>8}  {verdict}")
    return "\n".join(out)


def prom_audit(by_node: dict[int, list[dict]]) -> str:
    """Prometheus gauges for the audit plane (appended to prom_dump's
    exposition when a run directory carries audit sidecars)."""
    out: list[str] = []
    for name, help_text, fn in (
            ("audit_edges_total", "dependency edge lanes observed",
             lambda recs: sum(int(r.get("edge_cnt", 0)) for r in recs)),
            ("audit_epochs_total", "epochs exported by the audit plane",
             len),
            ("audit_dropped_total", "edges past the export cap",
             lambda recs: sum(int(r.get("dropped", 0)) for r in recs))):
        out.append(f"# HELP deneva_{name} {help_text}")
        out.append(f"# TYPE deneva_{name} gauge")
        for node in sorted(by_node):
            out.append(f'deneva_{name}{{node="{node}"}} '
                       f"{float(fn(by_node[node])):g}")
    return "\n".join(out) + "\n"


def resolve_stream(path: str) -> str:
    """Accept a stream file or a run directory (newest bus stream)."""
    if os.path.isdir(path):
        cands = sorted(f for f in os.listdir(path)
                       if f.startswith("metrics_bus_")
                       and f.endswith(".jsonl"))
        if not cands:
            raise FileNotFoundError(
                f"no metrics_bus_*.jsonl under {path} (run with "
                "--metrics=true)")
        return os.path.join(
            path, max(cands, key=lambda f: os.path.getmtime(
                os.path.join(path, f))))
    return path


def main(argv: list[str]) -> int:
    interval = 1.0
    args: list[str] = []
    i = 0
    while i < len(argv):
        if argv[i] == "--interval":
            interval = float(argv[i + 1])
            i += 2
        else:
            args.append(argv[i])
            i += 1
    pos = [a for a in args if not a.startswith("--")]
    if not pos:
        print("usage: python tools/monitor.py <metrics_bus.jsonl|run-dir>"
              " [--once|--prom] [--interval S]", file=sys.stderr)
        return 2
    path = resolve_stream(pos[0])
    if "--prom" in argv:
        sys.stdout.write(prom_dump(read_metrics(path)))
        aud = load_audit_dir(pos[0])
        if aud:
            sys.stdout.write(prom_audit(aud))
        return 0
    if "--once" in argv:
        rows = read_metrics(path)
        print(render_table(rows))
        ctrl = render_ctrl(rows)
        if ctrl:
            print()
            print(ctrl)
        aud = load_audit_dir(pos[0])
        if aud:
            print()
            print(render_audit(aud))
        return 0
    try:
        while True:
            rows = read_metrics(path)
            sys.stdout.write("\x1b[2J\x1b[H")       # clear + home
            print(f"metrics bus  {path}  "
                  f"({len(rows)} records, ^C to quit)\n")
            print(render_table(rows))
            ctrl = render_ctrl(rows)
            if ctrl:
                print()
                print(ctrl)
            aud = load_audit_dir(pos[0])
            if aud:
                print()
                print(render_audit(aud))
            sys.stdout.flush()
            time.sleep(interval)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
