#!/usr/bin/env python
"""Host-path microbench (host-path pipeline PR).

Measures the per-GROUP cost of every host stage the cluster steady loop
pays between two device dispatches, old path vs zero-copy path, at real
cluster shapes:

* assembly  — admission blocks -> stacked [C, b] device feed
              (QueryBlock.concat + zeros + fill  vs  direct-fill into
              reused buffers)
* bcast     — my contribution -> EPOCH_BLOB on the wire per peer
              (encode_epoch_blob bytes + dt_send  vs  parts + dt_sendv)
* decode    — peer blobs -> feed slices
              (decode_epoch_blob alloc + fill  vs  decode_epoch_blob_into)
* log       — merged feed -> framed log record
              (encode_epoch_blob + pack_record  vs  pack_record_views)
* retire    — packed verdict planes -> CL_RSP payloads on the wire
              (unpackbits + encode_cl_rsp + dt_send  vs  prefetched
              unpack/split + cl_rsp_parts + dt_sendv)
* client    — ring block -> CL_QRY_BATCH on the wire
              (encode_qry_block + dt_send  vs  qry_block_parts + dt_sendv)

The BEFORE critical path is the sum of the stages the serial loop runs
on the dispatch thread; the AFTER critical path is what stays on the
dispatch thread under host_overlap (direct-fill assembly + decode-into +
stage submission), with the wire/retire-worker stage costs reported
separately — they overlap device compute.  The acceptance bar for this
PR is AFTER <= BEFORE/2.

Usage: python tools/wirebench.py [--reps N] [--out results/wirebench]
Writes <out>/WIREBENCH.json (provenance + per-stage ns/group) and prints
the BASELINE.md markdown table.
"""

import argparse
import datetime
import json
import os
import platform
import sys
import threading
import time
import uuid

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deneva_tpu.runtime import wire                      # noqa: E402
from deneva_tpu.runtime.logger import (pack_record,      # noqa: E402
                                       pack_record_views)
from deneva_tpu.runtime.native import (NativeTransport,  # noqa: E402
                                       ipc_endpoints)

# (name, n_srv, C, b_merged, W, S): the two shapes the PR's claims rest
# on — the cluster_scaling N=4 CPU shape and the single-TPU-server
# cluster shape (BASELINE.md cluster_tpu)
SHAPES = [
    ("cluster_scaling_N4", 4, 8, 256, 4, 0),
    ("cluster_tpu_1srv", 1, 32, 16384, 10, 0),
]


def _bench(fn, reps: int, warm: int = 2, rounds: int = 5,
           settle=None) -> float:
    """Best-of-rounds ns/op: the minimum across measurement rounds is
    the scheduler-noise-resistant estimator on a small shared box (the
    2-core rig runs bench + native sender + drainer threads).
    ``settle`` (e.g. transport-queue drain) runs between rounds so a
    send-heavy stage never measures its own backpressure."""
    for _ in range(warm):
        fn()
    best = float("inf")
    per_round = max(reps // rounds, 1)
    for _ in range(rounds):
        if settle is not None:
            settle()
        t0 = time.perf_counter_ns()
        for _ in range(per_round):
            fn()
        best = min(best, (time.perf_counter_ns() - t0) / per_round)
    return best


def _pieces(rng, n, W, S, parts=3):
    """A contribution as `parts` admission pieces (retry blocks + pending
    slices), like _contribution sees them."""
    cuts = sorted(rng.choice(max(n - 1, 1), size=min(parts - 1, n - 1),
                             replace=False) + 1) if n > 1 else []
    lo = 0
    out = []
    for hi in list(cuts) + [n]:
        m = hi - lo
        out.append(wire.QueryBlock(
            keys=rng.integers(0, 2**20, (m, W)).astype(np.int32),
            types=rng.integers(1, 4, (m, W)).astype(np.int8),
            scalars=rng.integers(0, 100, (m, S)).astype(np.int32),
            tags=rng.integers(0, 2**40, m).astype(np.int64)))
        lo = hi
    return out


def bench_shape(name, n_srv, C, b, W, S, reps) -> dict:
    rng = np.random.default_rng(42)
    b_loc = b // n_srv
    pieces = [_pieces(rng, b_loc, W, S) for _ in range(C)]
    my_ts = [rng.integers(1, 2**30, b_loc).astype(np.int64)
             for _ in range(C)]
    my_blocks = [wire.QueryBlock.concat(p) for p in pieces]
    peer_blobs = [[wire.encode_epoch_blob(i, my_blocks[i], my_ts[i])
                   for _ in range(n_srv - 1)] for i in range(C)]

    # a 2-node mesh so send-side costs are real enqueue+frame work; the
    # drainer keeps the bounded recv queue from backpressuring the bench
    eps = ipc_endpoints(2, uuid.uuid4().hex[:8])
    nodes = [NativeTransport(i, eps, 2, msg_size_max=65536)
             for i in range(2)]
    ths = [threading.Thread(target=t.start) for t in nodes]
    for t in ths:
        t.start()
    for t in ths:
        t.join()
    tp, sink = nodes
    stop = threading.Event()

    def drain():
        while not stop.is_set():
            sink.recv(20_000)

    drainer = threading.Thread(target=drain)
    drainer.start()

    def settle():
        # let the sender/drainer catch up so send-stage rounds measure
        # enqueue+frame cost, not the bounded queue's backpressure
        for _ in range(200):
            if tp.stats()["send_queue_depth"] == 0:
                break
            time.sleep(0.005)

    res = {}
    try:
        # ---- assembly ------------------------------------------------
        def assembly_old():
            keys = np.zeros((C, b, W), np.int32)
            types = np.zeros((C, b, W), np.int8)
            scal = np.zeros((C, b, S), np.int32)
            tags = np.zeros((C, b), np.int64)
            ts_np = np.zeros((C, b), np.int64)
            active = np.zeros((C, b), bool)
            for i in range(C):
                blk = wire.QueryBlock.concat(pieces[i])
                for s in range(n_srv):
                    o = s * b_loc
                    keys[i, o:o + b_loc] = blk.keys
                    types[i, o:o + b_loc] = blk.types
                    scal[i, o:o + b_loc] = blk.scalars
                    tags[i, o:o + b_loc] = blk.tags
                    ts_np[i, o:o + b_loc] = my_ts[i]
                    active[i, o:o + b_loc] = True
            return keys

        fs = {"keys": np.zeros((C, b, W), np.int32),
              "types": np.zeros((C, b, W), np.int8),
              "scal": np.zeros((C, b, S), np.int32),
              "tags": np.zeros((C, b), np.int64),
              "ts": np.zeros((C, b), np.int64),
              "active": np.zeros((C, b), bool)}

        def assembly_new():
            # mirror of the server path: only the active plane re-zeroes
            # (full slices here, so there is no tail to pad)
            fs["active"].fill(False)
            for i in range(C):
                n = 0
                for blk in pieces[i]:          # my slice: direct writes
                    m = len(blk)
                    fs["keys"][i, n:n + m] = blk.keys
                    fs["types"][i, n:n + m] = blk.types
                    fs["scal"][i, n:n + m] = blk.scalars
                    fs["tags"][i, n:n + m] = blk.tags
                    n += m
                fs["ts"][i, :b_loc] = my_ts[i]
                fs["active"][i, :b_loc] = True
                for s in range(1, n_srv):      # peers: decode into slices
                    o = s * b_loc
                    wire.decode_epoch_blob_into(
                        peer_blobs[i][s - 1], fs["tags"][i, o:o + b_loc],
                        fs["ts"][i, o:o + b_loc],
                        fs["keys"][i, o:o + b_loc],
                        fs["types"][i, o:o + b_loc],
                        fs["scal"][i, o:o + b_loc])
                    fs["active"][i, o:o + b_loc] = True

        # the old loop decodes peer blobs in _route (alloc) before fill
        def decode_old():
            for i in range(C):
                for blob in peer_blobs[i]:
                    wire.decode_epoch_blob(blob)

        res["assembly_old"] = _bench(assembly_old, reps) + \
            _bench(decode_old, reps)
        res["assembly_new"] = _bench(assembly_new, reps)

        # ---- bcast ---------------------------------------------------
        peers = max(n_srv - 1, 1)   # 1-server shapes still price the send

        def bcast_old():
            for i in range(C):
                blob = wire.encode_epoch_blob(i, my_blocks[i], my_ts[i])
                for _ in range(peers):
                    tp.send(1, "EPOCH_BLOB", blob)

        def bcast_new():
            for i in range(C):
                parts = wire.epoch_blob_parts(
                    i, my_ts[i], my_blocks[i].tags, my_blocks[i].keys,
                    my_blocks[i].types, my_blocks[i].scalars)
                tp.sendv_many([1] * peers, "EPOCH_BLOB", parts)

        res["bcast_old"] = _bench(bcast_old, reps, settle=settle)
        res["bcast_new"] = _bench(bcast_new, reps, settle=settle)

        # ---- log record ---------------------------------------------
        active = np.ones(b, bool)
        merged = wire.QueryBlock(fs["keys"][0], fs["types"][0],
                                 fs["scal"][0], fs["tags"][0])

        def log_old():
            for i in range(C):
                rec = wire.encode_epoch_blob(i, merged, fs["ts"][0])
                pack_record(i, rec, active)

        def log_new():
            for i in range(C):
                pack_record_views(i, fs["ts"][0], fs["tags"][0],
                                  fs["keys"][0], fs["types"][0],
                                  fs["scal"][0], active)

        res["log_old"] = _bench(log_old, reps)
        res["log_new"] = _bench(log_new, reps)

        # ---- retire --------------------------------------------------
        pb = (b_loc + 7) // 8 * 8
        pk = rng.integers(0, 256, (3, C, pb // 8)).astype(np.uint8)

        def unpack_and_split():
            planes = np.unpackbits(pk, axis=-1, bitorder="little")
            done = planes[0, :, :b_loc].astype(bool)
            out = []
            for i in range(C):
                tags = my_blocks[i].tags[done[i]]
                clients = tags >> 40
                out.append([(int(c), tags[clients == c])
                            for c in np.unique(clients)])
            return out

        split = unpack_and_split()

        def retire_old():
            for per_epoch in unpack_and_split():
                for c, tags in per_epoch:
                    tp.send(1, "CL_RSP", wire.encode_cl_rsp(tags))

        def retire_new_dispatch():
            # under overlap the unpack/split ran on the retire worker;
            # the dispatch thread only ships the precomputed payloads
            for per_epoch in split:
                for c, tags in per_epoch:
                    tp.sendv(1, "CL_RSP", wire.cl_rsp_parts(tags))

        res["retire_old"] = _bench(retire_old, reps, settle=settle)
        res["retire_new"] = _bench(retire_new_dispatch, reps, settle=settle)
        res["retire_prefetch_offthread"] = _bench(
            lambda: unpack_and_split(), reps)

        # ---- client send (per CL_QRY_BATCH of 1024) ------------------
        cq = wire.QueryBlock(
            keys=rng.integers(0, 2**20, (1024, W)).astype(np.int32),
            types=rng.integers(1, 4, (1024, W)).astype(np.int8),
            scalars=rng.integers(0, 100, (1024, S)).astype(np.int32),
            tags=np.arange(1024, dtype=np.int64))

        res["client_old"] = _bench(
            lambda: tp.send(1, "CL_QRY_BATCH", wire.encode_qry_block(cq)),
            reps * 4, settle=settle)
        res["client_new"] = _bench(
            lambda: tp.sendv(1, "CL_QRY_BATCH", wire.qry_block_parts(
                cq.tags, cq.keys, cq.types, cq.scalars)), reps * 4,
            settle=settle)
    finally:
        stop.set()
        drainer.join(timeout=5)
        tp.close()
        sink.close()

    res["critical_before"] = (res["assembly_old"] + res["bcast_old"]
                              + res["log_old"] + res["retire_old"])
    res["critical_after"] = (res["assembly_new"] + res["retire_new"])
    res["offthread_after"] = (res["bcast_new"] + res["log_new"]
                              + res["retire_prefetch_offthread"])
    res["reduction_x"] = res["critical_before"] / max(
        res["critical_after"], 1.0)
    return res


def main(argv) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--reps", type=int, default=30)
    ap.add_argument("--out", default="results/wirebench")
    args = ap.parse_args(argv)

    record = {
        "bench": "wirebench",
        "provenance": {
            "platform": platform.platform(),
            "python": platform.python_version(),
            "host": platform.node(),
            "captured": datetime.datetime.now().isoformat(
                timespec="seconds"),
            "capture": "host-CPU microbench (no device involved: these "
                       "stages run on the host either way)",
        },
        "unit": "ns_per_group",
        "shapes": {},
    }
    for name, n_srv, C, b, W, S in SHAPES:
        reps = args.reps if b <= 4096 else max(args.reps // 5, 3)
        res = bench_shape(name, n_srv, C, b, W, S, reps)
        record["shapes"][name] = {
            "n_srv": n_srv, "C": C, "b_merged": b, "W": W, "S": S,
            **{k: round(v, 1) for k, v in res.items()}}
        print(f"\n### wirebench {name} (n_srv={n_srv} C={C} b={b} W={W})")
        print("| stage | before ns/group | after ns/group | ratio |")
        print("|---|---|---|---|")
        for stage in ("assembly", "bcast", "log", "retire", "client"):
            o, n = res[f"{stage}_old"], res[f"{stage}_new"]
            print(f"| {stage} | {o:,.0f} | {n:,.0f} | {o / max(n, 1):.1f}x |")
        print(f"| **dispatch-thread critical path** | "
              f"**{res['critical_before']:,.0f}** | "
              f"**{res['critical_after']:,.0f}** | "
              f"**{res['reduction_x']:.1f}x** |")
        print(f"| (moved off-thread, overlaps device) | - | "
              f"{res['offthread_after']:,.0f} | - |")
    os.makedirs(args.out, exist_ok=True)
    path = os.path.join(args.out, "WIREBENCH.json")
    with open(path, "w") as f:
        json.dump(record, f, indent=1)
    print(f"\nwrote {path}")
    # the gate reads the HOST-BOUND shape (the big-blob cluster shape
    # where the serial host path actually binds the loop — round-2
    # measured 430 ms/epoch there).  The small N4 CPU shape is
    # informational: at 64-row messages per-call overheads bound the
    # wire stages (~parity) and its whole host path is ~1 ms/group,
    # 25x below that shape's ~5 ms epochs — not the binder.
    gated = record["shapes"]["cluster_tpu_1srv"]["reduction_x"]
    small = record["shapes"]["cluster_scaling_N4"]["reduction_x"]
    print(f"host-bound-shape critical-path reduction: {gated:.1f}x "
          f"(acceptance bar: >= 2x); small-shape (informational): "
          f"{small:.1f}x")
    return 0 if gated >= 2.0 else 1


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
