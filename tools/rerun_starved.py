"""Re-measure sweep points whose first pass was starved by host-side CPU
contention (epochs/sec collapsed; flagged by the epoch_cnt/total_runtime
scan).  Must run on a quiet machine — measurement is host-pacing
sensitive over the tunneled chip."""

from __future__ import annotations

import sys

sys.path.insert(0, "/root/repo")

from deneva_tpu.config import CCAlg  # noqa: E402
from deneva_tpu.harness.experiments import (ALL_ALGS, get_experiment,  # noqa: E402
                                            paper_base)
from deneva_tpu.harness.run import run_point  # noqa: E402


def bench(cfgs):
    return [c.replace(warmup_secs=1.5, done_secs=4.0) for c in cfgs]


def main() -> int:
    base = paper_base(False)
    jobs = []
    # ycsb_skew: every alg at theta 0.6 and 0.9, plus TPU_BATCH at 0.3
    skew = [base.replace(zipf_theta=t, cc_alg=CCAlg(a))
            for t in (0.6, 0.9) for a in ALL_ALGS]
    skew.append(base.replace(zipf_theta=0.3, cc_alg=CCAlg.TPU_BATCH))
    jobs.append(("ycsb_skew", bench(skew)))
    op = base.replace(zipf_theta=0.9)
    jobs.append(("operating_points", bench(
        [op.replace(cc_alg=CCAlg.MAAT, epoch_batch=8192),
         op.replace(cc_alg=CCAlg.MVCC, epoch_batch=8192)])))
    jobs.append(("isolation_levels", bench(
        [c for c in get_experiment("isolation_levels", quick=False)
         if c.isolation_level == "SERIALIZABLE"])))
    for name, cfgs in jobs:
        print(f"[{name}] rerun {len(cfgs)} points", flush=True)
        for cfg in cfgs:
            run_point(cfg, f"results/{name}", quiet=False)
    print("RERUN_STARVED_DONE", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
