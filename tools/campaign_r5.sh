#!/bin/bash
# Round-5 full re-measurement campaign (VERDICT r4 next #8): every sweep
# re-run against round-5 code — the RA-pass trims, the ts-only MVCC ring,
# the sort-based last_writer, the Pallas deletion and the host thread
# axes all change measured numbers, so no stale number may survive in
# results/.  Sequential: single-client TPU tunnel, one host core.
# --bench = full problem sizes, short windows (the rounds-2/3 tier).
cd /root/repo
set -x
for exp in ycsb_skew tpcc_scaling ycsb_inflight isolation_levels \
           escrow_ablation modes cluster_scaling network_sweep \
           operating_points ycsb_hot ycsb_writes ycsb_scaling \
           ycsb_partitions pps_scaling; do
  timeout 5400 python -m deneva_tpu.harness.run "$exp" --bench \
    || echo "FAILED: $exp"
  echo "DONE: $exp"
done
timeout 1800 python tools/measure_cluster_tpu.py || echo "FAILED: cluster_tpu"
echo CAMPAIGN_R5_DONE
