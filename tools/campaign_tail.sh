#!/bin/bash
cd /root/repo
set -x
# no -e: the two sweeps are independent — a timeout in one must not
# skip the other (campaign_final.sh is -e because its stages feed each
# other)
timeout 3600 python -m deneva_tpu.harness.run ycsb_hot --bench
timeout 3600 python -m deneva_tpu.harness.run ycsb_inflight --bench
echo TAIL_DONE
