"""Telemetry-overhead bench: produce the results/telemetry pairs the
regression gate checks.

For each preset, runs the SAME CI-sized cluster config with the flight
recorder off and armed at the default ``telemetry_sample`` (1024),
alternating arms ``--repeat`` times, and writes:

  results/telemetry/<preset>_off.out    median-tput off run
  results/telemetry/<preset>_on.out     median-tput armed run
  results/telemetry/<preset>_waterfall.txt   per-stage p50/p95/p99
                                             waterfall of a DENSE-sample
                                             run (sample=8) of the same
                                             preset, via txntrace

The ``.out`` files carry the standard ``# cfg`` echo + the server-0 and
client ``[summary]`` lines, so ``harness.parse.load_results`` reads them
like any sweep point; ``tools/regression_gate.py check`` then enforces
armed tput >= 98% of off AND tel_sampled_cnt > 0 (anti-inert +
anti-regression in one gate — see TELEMETRY_TOLERANCE there).

Usage:  python tools/telemetry_bench.py [--repeat 3] [--out results/telemetry]
                                        [--preset ycsb_zipf09 ...]
"""

from __future__ import annotations

import os
import statistics
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deneva_tpu.config import CCAlg, Config, WorkloadKind  # noqa: E402
from deneva_tpu.harness.parse import cfg_header  # noqa: E402
from deneva_tpu.stats import parse_summary  # noqa: E402

LOG_DIR = os.environ.get("TELBENCH_DIR", "/dev/shm/deneva_telbench")

# CI-sized presets (the chaos-harness cluster shape): the two the
# acceptance pins — hot-key YCSB and the overload flash crowd
PRESETS: dict[str, dict] = {
    # epoch_batch 1024 (production-shaped, not the chaos harness's
    # jit-fast 256): the per-epoch host costs the recorder adds
    # (verdict mask, metrics line) amortize over the batch exactly as
    # they do at the default 2048 — a 256-batch CI config overstates
    # per-epoch overhead ~4x.  OPEN-LOOP at 45 k/s (~60-70% of this
    # box's 65-88 k/s saturated band): saturated closed-loop tput on
    # the contended 2-core CI box swings ±10% run to run (armed runs
    # beat off runs as often as not — BASELINE round-15 records the
    # saturated medians with that caveat), which no 2% gate can ride;
    # pinning the offered load makes the pair reproducible to ±0.1%
    # and turns the gate into the production question — the armed
    # server must HOLD the same offered load with no shedding/backlog.
    "ycsb_zipf09": dict(
        workload=WorkloadKind.YCSB, cc_alg=CCAlg.CALVIN,
        node_cnt=2, client_node_cnt=1, epoch_batch=1024,
        conflict_buckets=512, synth_table_size=8192,
        max_txn_in_flight=4096, req_per_query=4, max_accesses=4,
        zipf_theta=0.9, warmup_secs=1.0, done_secs=4.0,
        arrival_process="poisson", arrival_rate=45000.0,
        logging=True, replica_cnt=1, log_dir=LOG_DIR),
    "overload_flash": dict(
        workload=WorkloadKind.YCSB, cc_alg=CCAlg.CALVIN,
        node_cnt=2, client_node_cnt=1, epoch_batch=256,
        conflict_buckets=512, synth_table_size=8192,
        max_txn_in_flight=16384, req_per_query=4, max_accesses=4,
        zipf_theta=0.6, warmup_secs=1.0, done_secs=6.0,
        admission=True, admission_queue_max=1024,
        arrival_process="flash", arrival_rate=5000.0,
        arrival_flash_at_s=2.5, arrival_flash_secs=1.5,
        arrival_flash_factor=10.0, log_dir=LOG_DIR),
}


def _run(cfg: Config, run_id: str) -> dict[str, dict]:
    from deneva_tpu.runtime.launch import run_cluster
    out = run_cluster(cfg, platform="cpu", run_id=run_id)
    return {f"{kind}{nid}": parse_summary(line)
            for nid, (kind, line) in out.items() if line}


def _write_out(path: str, cfg: Config, reports: list[dict]) -> None:
    """Standard .out shape: cfg echo + client then server-0 summary
    (parse takes the LAST [summary] line — the server's tput is the
    gate's comparand)."""
    from deneva_tpu.stats import Stats
    with open(path, "w") as f:
        f.write(cfg_header(cfg))
        for rep, tag in ((r, t) for r in reports
                         for t in ("client2", "server0")):
            fields = rep.get(tag)
            if fields is None:
                continue
            st = Stats()
            for k, v in fields.items():
                st.set(k, v)
            f.write(st.summary_line() + "\n")


def bench_preset(name: str, repeat: int, out_dir: str) -> None:
    import numpy as np

    base = Config(**PRESETS[name])
    runs: dict[str, list[dict]] = {"off": [], "on": []}
    for r in range(repeat):
        for arm in ("off", "on"):
            cfg = base if arm == "off" else base.replace(telemetry=True)
            rep = _run(cfg, f"telbench_{name}_{arm}_{r}_{os.getpid()}")
            tput = rep["server0"]["tput"]
            print(f"[telemetry_bench] {name} {arm} run {r}: "
                  f"tput={tput:.0f}", flush=True)
            runs[arm].append(rep)
    os.makedirs(out_dir, exist_ok=True)
    meds = {}
    for arm in ("off", "on"):
        tputs = [r["server0"]["tput"] for r in runs[arm]]
        med = runs[arm][int(np.argsort(tputs)[len(tputs) // 2])]
        meds[arm] = med["server0"]["tput"]
        cfg = base if arm == "off" else base.replace(telemetry=True)
        _write_out(os.path.join(out_dir, f"{name}_{arm}.out"), cfg,
                   [med])
    ratio = meds["on"] / max(meds["off"], 1e-9)
    print(f"[telemetry_bench] {name}: off={meds['off']:.0f} "
          f"on={meds['on']:.0f} ratio={ratio:.4f} "
          f"(median of {repeat}; spread off="
          f"{statistics.pstdev([r['server0']['tput'] for r in runs['off']]):.0f})",
          flush=True)
    # dense-sample run for the checked-in waterfall (sample=8: enough
    # chains for stable p99s; NOT the overhead arm)
    from deneva_tpu.harness import txntrace
    wcfg = base.replace(telemetry=True, telemetry_sample=8)
    run_id = f"telbench_{name}_wf_{os.getpid()}"
    _run(wcfg, run_id)
    recs, _roles = txntrace.load_dir(os.path.join(LOG_DIR, run_id))
    chains = [txntrace.build_chain(ev)
              for ev in txntrace.index_txns(recs).values()]
    committed, full, viol = txntrace.completeness(chains)
    with open(os.path.join(out_dir, f"{name}_waterfall.txt"), "w") as f:
        f.write(f"# per-stage latency waterfall — preset {name}, "
                f"telemetry_sample=8 (dense), CPU cluster 2s1c\n")
        f.write(f"# {len(chains)} sampled txns, {committed} committed, "
                f"{full} full quorum chains, {len(viol)} violations\n")
        f.write(txntrace.render(txntrace.waterfall(chains, "verdict"))
                + "\n")
    print(f"[telemetry_bench] {name}: waterfall over {committed} "
          f"committed chains ({len(viol)} violations)", flush=True)


def main(argv: list[str]) -> int:
    repeat = 3
    out_dir = "results/telemetry"
    names = []
    i = 0
    while i < len(argv):
        if argv[i] == "--repeat":
            repeat = int(argv[i + 1]); i += 2
        elif argv[i] == "--out":
            out_dir = argv[i + 1]; i += 2
        elif argv[i] == "--preset":
            names.append(argv[i + 1]); i += 2
        else:
            print(f"unknown arg {argv[i]!r}", file=sys.stderr)
            return 2
    for name in (names or list(PRESETS)):
        bench_preset(name, repeat, out_dir)
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
