#!/bin/bash
# Round-4 full re-measurement campaign (VERDICT r3 next #1/#2):
# every sweep re-run against round-4 code so no number in results/
# describes behavior the code doesn't have.  Sequential: single-client
# TPU tunnel.  Priority order = the VERDICT's named sweeps first.
# --bench = full problem sizes, short windows (the rounds-2/3 tier).
cd /root/repo
set -x
for exp in tpcc_scaling ycsb_skew ycsb_inflight isolation_levels \
           escrow_ablation modes cluster_scaling network_sweep \
           operating_points ycsb_hot ycsb_writes ycsb_scaling \
           ycsb_partitions pps_scaling; do
  timeout 5400 python -m deneva_tpu.harness.run "$exp" --bench \
    || echo "FAILED: $exp"
  echo "DONE: $exp"
done
timeout 1200 python tools/measure_cluster_tpu.py || echo "FAILED: cluster_tpu"
echo CAMPAIGN_R4_DONE
