"""Trimmed round-2 sweep continuation (single-chip tunnel time budget).

Runs the highest-value subsets of the remaining paper sweeps at full
problem sizes with bench windows; full grids stay available via
``python -m deneva_tpu.harness.run <exp> --bench``.
"""

from __future__ import annotations

import sys

sys.path.insert(0, "/root/repo")

from deneva_tpu.config import CCAlg  # noqa: E402
from deneva_tpu.harness.experiments import (ALL_ALGS, get_experiment,  # noqa: E402
                                            paper_base)
from deneva_tpu.harness.run import run_point  # noqa: E402


def bench(cfgs):
    return [c.replace(warmup_secs=1.5, done_secs=4.0) for c in cfgs]


def main() -> int:
    jobs: list[tuple[str, list]] = []

    if "escrow" in sys.argv:
        jobs.append(("escrow_ablation", bench(
            get_experiment("escrow_ablation", quick=False))))
    if "skew" in sys.argv:
        jobs.append(("ycsb_skew", bench(
            get_experiment("ycsb_skew", quick=False))))
    if "writes" in sys.argv:
        base = paper_base(False).replace(zipf_theta=0.6)
        cfgs = [base.replace(read_perc=1 - w, write_perc=w,
                             cc_alg=CCAlg(a))
                for w in (0.0, 0.5, 1.0) for a in ALL_ALGS]
        jobs.append(("ycsb_writes", bench(cfgs)))
    if "tpcc" in sys.argv:
        base = paper_base(False).replace(workload="TPCC", max_accesses=32)
        # wh axis endpoints (the 16-wh midpoint interpolates; chip time
        # budget)
        cfgs = [base.replace(num_wh=wh, perc_payment=0.5, cc_alg=CCAlg(a))
                for wh in (4, 64) for a in ALL_ALGS]
        jobs.append(("tpcc_scaling", bench(cfgs)))
    if "tpcc16" in sys.argv:    # grid midpoint (run post-campaign)
        base = paper_base(False).replace(workload="TPCC", max_accesses=32)
        jobs.append(("tpcc_scaling", bench(
            [base.replace(num_wh=16, perc_payment=0.5, cc_alg=CCAlg(a))
             for a in ALL_ALGS])))
    if "pps" in sys.argv:
        jobs.append(("pps_scaling", bench(
            get_experiment("pps_scaling", quick=False))))
    if "modes" in sys.argv:
        jobs.append(("modes", bench(get_experiment("modes", quick=False))))

    for name, cfgs in jobs:
        out_dir = f"results/{name}"
        print(f"[{name}] {len(cfgs)} points -> {out_dir}", flush=True)
        for cfg in cfgs:
            run_point(cfg, out_dir, quiet=False)
    print("CAMPAIGN_B_DONE", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
