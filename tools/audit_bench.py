"""Isolation-audit overhead bench: produce the results/audit pairs the
regression gate checks (the telemetry_bench.py / metricsbus_bench.py
pattern applied to the serializability certifier).

For each preset, runs the SAME CI-sized open-loop cluster config with
the audit plane off and armed at the default ``audit_cadence``
(epoch-sampled certification — the shipping rate; chaos scenarios pin
cadence=1 for full coverage), alternating arms
``--repeat`` times, and writes:

  results/audit/<preset>_off.out       median-tput off run
  results/audit/<preset>_on.out        median-tput armed run
  results/audit/<preset>_cert.txt      the armed median run's
                                       serializability certificate
                                       (harness/auditgraph.py render)

The ``.out`` files carry the standard ``# cfg`` echo + the server-0 and
client ``[summary]`` lines; ``tools/regression_gate.py check`` then
enforces armed tput >= 98% of off AND audit_edges_exported > 0
(anti-inert + anti-regression in one gate — see telemetry_violations
there).  The preset is contended CALVIN on purpose: the forwarding
executor's in-batch read forwarding produces real wr/rw edges every
epoch, so an armed run that exports zero edges is provably inert.

Usage:  python tools/audit_bench.py [--repeat 3]
            [--out results/audit] [--preset ycsb_zipf09]
"""

from __future__ import annotations

import os
import statistics
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deneva_tpu.config import CCAlg, Config, WorkloadKind  # noqa: E402
from deneva_tpu.harness.parse import cfg_header  # noqa: E402
from deneva_tpu.stats import parse_summary  # noqa: E402

LOG_DIR = os.environ.get("AUDITBENCH_DIR", "/dev/shm/deneva_auditbench")

# CI-sized preset (the metricsbus bench's open-loop shape: a pinned
# offered load makes the pair reproducible to ~±0.1% where saturated
# closed-loop tput swings ±10% on the contended 2-core box — the gate
# question becomes "does the armed server HOLD the same offered load").
PRESETS: dict[str, dict] = {
    "ycsb_zipf09": dict(
        workload=WorkloadKind.YCSB, cc_alg=CCAlg.CALVIN,
        node_cnt=2, client_node_cnt=1, epoch_batch=1024,
        conflict_buckets=512, synth_table_size=8192,
        max_txn_in_flight=4096, req_per_query=4, max_accesses=4,
        zipf_theta=0.9, warmup_secs=1.0, done_secs=4.0,
        arrival_process="poisson", arrival_rate=45000.0,
        logging=True, replica_cnt=1, log_dir=LOG_DIR),
}


def _run(cfg: Config, run_id: str) -> tuple[dict[str, dict], str]:
    from deneva_tpu.runtime.launch import run_cluster
    out = run_cluster(cfg, platform="cpu", run_id=run_id)
    return ({f"{kind}{nid}": parse_summary(line)
             for nid, (kind, line) in out.items() if line},
            os.path.join(LOG_DIR, run_id))


def _write_out(path: str, cfg: Config, rep: dict) -> None:
    from deneva_tpu.stats import Stats
    with open(path, "w") as f:
        f.write(cfg_header(cfg))
        for tag in ("client2", "server0"):
            fields = rep.get(tag)
            if fields is None:
                continue
            st = Stats()
            for k, v in fields.items():
                st.set(k, v)
            f.write(st.summary_line() + "\n")


def bench_preset(name: str, repeat: int, out_dir: str) -> None:
    import numpy as np

    base = Config(**PRESETS[name])
    runs: dict[str, list[dict]] = {"off": [], "on": []}
    on_dirs: list[str] = []
    for r in range(repeat):
        for arm in ("off", "on"):
            cfg = base if arm == "off" else base.replace(audit=True)
            rep, rdir = _run(cfg,
                             f"auditbench_{name}_{arm}_{r}_{os.getpid()}")
            if arm == "on":
                on_dirs.append(rdir)
            tput = rep["server0"]["tput"]
            print(f"[audit_bench] {name} {arm} run {r}: "
                  f"tput={tput:.0f}", flush=True)
            runs[arm].append(rep)
    os.makedirs(out_dir, exist_ok=True)
    meds = {}
    med_idx = {}
    for arm in ("off", "on"):
        tputs = [r["server0"]["tput"] for r in runs[arm]]
        i = int(np.argsort(tputs)[len(tputs) // 2])
        med_idx[arm] = i
        meds[arm] = runs[arm][i]["server0"]["tput"]
        cfg = base if arm == "off" else base.replace(audit=True)
        _write_out(os.path.join(out_dir, f"{name}_{arm}.out"), cfg,
                   runs[arm][i])
    ratio = meds["on"] / max(meds["off"], 1e-9)
    print(f"[audit_bench] {name}: off={meds['off']:.0f} "
          f"on={meds['on']:.0f} ratio={ratio:.4f} "
          f"(median of {repeat}; spread off="
          f"{statistics.pstdev([r['server0']['tput'] for r in runs['off']]):.0f})",
          flush=True)
    # checked-in certificate sample: what the armed median run proved
    from deneva_tpu.harness import auditgraph
    cert = auditgraph.certify(on_dirs[med_idx["on"]])
    with open(os.path.join(out_dir, f"{name}_cert.txt"), "w") as f:
        f.write(f"# serializability certificate — preset {name}, "
                f"default audit_cadence, CPU cluster 2s1c\n\n")
        f.write(auditgraph.render(cert) + "\n")
    print(f"[audit_bench] {name}: certificate ok={cert['ok']} "
          f"epochs={cert['epochs']} edges={cert['edges_deduped']}",
          flush=True)


def main(argv: list[str]) -> int:
    repeat = 3
    out_dir = "results/audit"
    names = []
    i = 0
    while i < len(argv):
        if argv[i] == "--repeat":
            repeat = int(argv[i + 1]); i += 2
        elif argv[i] == "--out":
            out_dir = argv[i + 1]; i += 2
        elif argv[i] == "--preset":
            names.append(argv[i + 1]); i += 2
        else:
            print(f"unknown arg {argv[i]!r}", file=sys.stderr)
            return 2
    for name in (names or list(PRESETS)):
        bench_preset(name, repeat, out_dir)
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
