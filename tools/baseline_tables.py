"""Render the measured-results tables for BASELINE.md from results/.

Usage: python tools/baseline_tables.py > /tmp/tables.md
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deneva_tpu.harness.parse import load_results  # noqa: E402


def pivot(exp: str, x: str, y: str = "tput", series: str = "cc_alg",
          fmt: str = "{:,.0f}") -> str:
    rows = load_results(f"results/{exp}")
    table: dict = {}
    xs = set()
    for r in rows:
        if y not in r or x not in r:
            continue
        s = r.get(series, "?")
        table.setdefault(s, {})[r[x]] = r[y]
        xs.add(r[x])
    if not table:
        return f"(no data for {exp})\n"
    xs = sorted(xs)
    out = [f"| {series} \\ {x} | " + " | ".join(str(v) for v in xs) + " |",
           "|" + "---|" * (len(xs) + 1)]
    for s in sorted(table, key=str):
        cells = [fmt.format(table[s][v]) if v in table[s] else "-"
                 for v in xs]
        out.append(f"| {s} | " + " | ".join(cells) + " |")
    return "\n".join(out) + "\n"


def listing(exp: str, fields=("tput", "abort_rate")) -> str:
    rows = load_results(f"results/{exp}")
    out = []
    for r in sorted(rows, key=lambda r: r["file"]):
        vals = "  ".join(f"{f}={r.get(f, 0):,.3g}" for f in fields
                         if f in r)
        out.append(f"  {r['file'][:-4]:62s} {vals}")
    return "\n".join(out) + "\n"


def frontier(exp: str) -> str:
    """Cluster latency/throughput frontier (VERDICT r4 next #5): per
    point, the server tput next to the CLIENT-observed end-to-end p50 and
    p99 (worst client).  Client summaries ride the '# node N (client)'
    lines of each .out; the plain parser only surfaces the server's."""
    import glob
    import re

    from deneva_tpu.stats import parse_summary
    out = [f"| point | tput | client p50 s | p99 s |",
           "|---|---|---|---|"]
    # harness/run.py writes peers as '# node N (kind): [summary] ...'
    # and the primary server's bare '[summary] ...' line; anchor on the
    # explicit client marker so a node-prefix drift can never
    # misattribute a client row as the server (ADVICE r5)
    client_re = re.compile(r"^# node \d+ \(client\):")
    for path in sorted(glob.glob(f"results/{exp}/*.out")):
        tput, p50, p99 = None, 0.0, 0.0
        for line in open(path):
            if "[summary]" not in line:
                continue
            f = parse_summary(line[line.index("[summary]"):])
            if client_re.match(line):      # a client node
                p50 = max(p50, f.get("client_client_latency_p50", 0.0))
                p99 = max(p99, f.get("client_client_latency_p99", 0.0))
            elif not line.startswith("#"):  # the server's own line
                tput = f.get("tput")
        if tput is None:
            continue
        stem = __import__("os").path.basename(path)[:-4]
        out.append(f"| {stem} | {tput:,.0f} | {p50:.3f} | {p99:.3f} |")
    return "\n".join(out) + "\n"


def main() -> int:
    print("### ycsb_skew (tput, txn/s)\n")
    print(pivot("ycsb_skew", "zipf_theta"))
    print("\n### ycsb_skew (abort rate)\n")
    print(pivot("ycsb_skew", "zipf_theta", y="abort_rate", fmt="{:.3f}"))
    print("\n### ycsb_writes (tput vs write fraction)\n")
    print(pivot("ycsb_writes", "write_perc"))
    print("\n### tpcc_scaling (tput vs warehouses, 50% payment)\n")
    print(pivot("tpcc_scaling", "num_wh"))
    print("\n### pps_scaling\n")
    print(listing("pps_scaling"))
    print("\n### ycsb_hot (HOT skew: tput vs hot-set access fraction)\n")
    print(pivot("ycsb_hot", "access_perc"))
    print("\n### ycsb_inflight (tput vs MAX_TXN_IN_FLIGHT)\n")
    print(pivot("ycsb_inflight", "max_txn_in_flight"))
    print("\n### operating_points (zipf 0.9)\n")
    print(pivot("operating_points", "epoch_batch"))
    print("\n### escrow_ablation\n")
    print(listing("escrow_ablation"))
    print("\n### isolation_levels (NO_WAIT + WAIT_DIE)\n")
    print(pivot("isolation_levels", "isolation_level", series="cc_alg"))
    print("\n### modes\n")
    print(pivot("modes", "mode", series="cc_alg"))
    print("\n### cluster_scaling (CPU, multi-process)\n")
    print(pivot("cluster_scaling", "node_cnt"))
    print("\n### cluster_tpu (1 TPU server + CPU clients)\n")
    print(listing("cluster_tpu"))
    print("\n### cluster_tpu latency/throughput frontier "
          "(client-observed e2e)\n")
    print(frontier("cluster_tpu"))
    return 0


if __name__ == "__main__":
    sys.exit(main())
